"""Lock-discipline / race detection (DC100-DC103).

Per class, infer the set of lock attributes (``self._lock =
threading.Lock()`` and friends), which methods run on their own threads
(``Thread(target=self.m)``, ``TaskPool(self.m, ...)``,
``run_in_executor(None, self.m)``), and which attribute accesses happen
under ``with self._lock:``. Then:

* **DC100** — attribute written both under a lock and outside any lock
  (in a non-``__init__`` method): the guard is advisory, i.e. broken.
* **DC101** — attribute written without a lock inside a thread-entry
  method while some *other* method also touches it: a cross-thread race.
* **DC102** — attribute explicitly declared ``guarded-by(L)`` written
  without holding ``L``.
* **DC103** — non-atomic read-modify-write (``self.x += 1``) outside any
  lock in a class that owns locks or threads: the classic lost update.

Methods named ``*_locked`` or annotated ``holds-lock(L)`` are treated as
running with the lock held (callers take it). ``unguarded-ok(reason)``
on any write site exempts that attribute (single-owner state, GIL-atomic
appends, event-loop-confined counters — intent, documented). ``__init__``
writes never count: construction happens-before publication.

Scope: the threaded serving tiers (``distributed/``, ``serving/``,
``disagg/``, ``utils/``). The engine is excluded by path — its
lock-free admission fast path is a documented design (engine.py keeps
GIL-atomic deque/dict handoffs on purpose) that a lock-inference pass
would misread.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, SourceFile, call_name, register, self_attr

_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore")
_THREAD_SPAWNERS = ("Thread", "Timer", "TaskPool")
_MUTATORS = {
    "append", "extend", "insert", "pop", "popleft", "appendleft", "remove",
    "discard", "add", "clear", "update", "setdefault",
}
_INIT_METHODS = {"__init__", "__new__", "__post_init__"}
# Directories whose files this checker skips (documented lock-free designs
# and pure-math code where lock inference has nothing to say).
_SKIP_SEGMENTS = {"engine", "models", "ops", "kernels", "pallas"}


@dataclasses.dataclass
class Access:
    method: str
    line: int
    kind: str  # 'write' | 'aug' | 'mutate' | 'read'
    locks: Tuple[str, ...]  # locks held at the access site


class _MethodScan(ast.NodeVisitor):
    """Record attribute accesses + held-lock sets within one method."""

    def __init__(self, cls: "_ClassInfo", method: str, base_locks: Set[str]):
        self.cls = cls
        self.method = method
        self.locks: List[str] = sorted(base_locks)

    def _record(self, attr: str, line: int, kind: str) -> None:
        self.cls.accesses.setdefault(attr, []).append(
            Access(self.method, line, kind, tuple(self.locks))
        )

    def visit_With(self, node: ast.With) -> None:
        held = []
        for item in node.items:
            ctx = item.context_expr
            attr = self_attr(ctx)
            if attr is None and isinstance(ctx, ast.Call):
                attr = self_attr(ctx.func)  # with self._cond: vs .acquire()
            if attr is not None and attr in self.cls.lock_attrs:
                held.append(attr)
        self.locks.extend(held)
        self.generic_visit(node)
        for _ in held:
            self.locks.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._targets(tgt)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._targets(node.target)
        if node.value is not None:
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self_attr(node.target)
        if attr is not None:
            self._record(attr, node.lineno, "aug")
        else:
            self._targets(node.target)
        self.visit(node.value)

    def _targets(self, tgt: ast.AST) -> None:
        attr = self_attr(tgt)
        if attr is not None:
            self._record(attr, tgt.lineno, "write")
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._targets(elt)
        elif isinstance(tgt, ast.Subscript):
            base = self_attr(tgt.value)
            if base is not None:
                self._record(base, tgt.lineno, "mutate")
            else:
                self.visit(tgt.value)
            self.visit(tgt.slice)
        elif isinstance(tgt, ast.Attribute):
            self.visit(tgt.value)

    def visit_Call(self, node: ast.Call) -> None:
        # self.x.append(...) — mutation of self.x through a method.
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            base = self_attr(node.func.value)
            if base is not None:
                self._record(base, node.lineno, "mutate")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._record(attr, node.lineno, "read")
        self.generic_visit(node)

    # Don't descend into nested defs/classes: their bodies run later, on
    # whatever thread calls them — a separate analysis unit.
    def visit_FunctionDef(self, node):  # noqa: D102
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):  # noqa: D102
        pass


@dataclasses.dataclass
class _ClassInfo:
    name: str
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    thread_entries: Set[str] = dataclasses.field(default_factory=set)
    spawns_threads: bool = False
    accesses: Dict[str, List[Access]] = dataclasses.field(default_factory=dict)
    declared: Dict[str, Tuple[str, int]] = dataclasses.field(
        default_factory=dict
    )  # attr -> (lock, decl line)
    exempt: Set[str] = dataclasses.field(default_factory=set)


def _scan_class(sf: SourceFile, node: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(node.name)
    methods = [
        n for n in node.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    # Pass 1: lock attrs, thread entries, declarations, exemptions.
    for m in methods:
        for sub in ast.walk(m):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                ctor = call_name(sub.value).rsplit(".", 1)[-1]
                if ctor in _LOCK_CTORS:
                    for tgt in sub.targets:
                        attr = self_attr(tgt)
                        if attr is not None:
                            info.lock_attrs.add(attr)
            if isinstance(sub, ast.Call):
                fn = call_name(sub).rsplit(".", 1)[-1]
                if fn in _THREAD_SPAWNERS or fn in (
                    "run_in_executor", "submit", "call_soon_threadsafe",
                ):
                    if fn in ("Thread", "Timer"):
                        info.spawns_threads = True
                    for arg in list(sub.args) + [
                        kw.value for kw in sub.keywords
                    ]:
                        attr = self_attr(arg)
                        if attr is not None:
                            info.thread_entries.add(attr)
            if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = (
                    sub.targets if isinstance(sub, ast.Assign)
                    else [sub.target]
                )
                for tgt in tgts:
                    attr = self_attr(tgt)
                    if attr is None:
                        continue
                    decl = sf.ann.at(tgt.lineno, "guarded-by")
                    if decl:
                        info.declared[attr] = (decl.strip(), tgt.lineno)
                    if sf.ann.at(tgt.lineno, "unguarded-ok") is not None:
                        info.exempt.add(attr)
    # Pass 2: access scan with lock tracking.
    for m in methods:
        base: Set[str] = set()
        held = sf.ann.at(m.lineno, "holds-lock")
        if held:
            base.update(a.strip() for a in held.split(",") if a.strip())
        if m.name.endswith("_locked"):
            base.update(info.lock_attrs)
        scan = _MethodScan(info, m.name, base)
        for stmt in m.body:
            scan.visit(stmt)
    return info


def _check_class(sf: SourceFile, info: _ClassInfo) -> List[Finding]:
    out: List[Finding] = []
    has_concurrency = bool(info.lock_attrs) or info.spawns_threads
    for attr, accs in sorted(info.accesses.items()):
        if attr in info.lock_attrs or attr in info.exempt:
            continue
        symbol = f"{info.name}.{attr}"
        writes = [a for a in accs if a.kind in ("write", "aug", "mutate")]
        eff_writes = [w for w in writes if w.method not in _INIT_METHODS]
        guarded = [w for w in eff_writes if w.locks]
        unguarded = [w for w in eff_writes if not w.locks]

        decl = info.declared.get(attr)
        if decl is not None:
            lock, _ = decl
            bad = [w for w in eff_writes if lock not in w.locks]
            if bad:
                w = bad[0]
                out.append(Finding(
                    "DC102", sf.path, w.line, symbol,
                    f"{symbol} is declared guarded-by({lock}) but "
                    f"{w.method}() writes it without holding self.{lock}",
                ))
            continue  # an explicit declaration supersedes inference

        if guarded and unguarded:
            w = unguarded[0]
            locks = ", ".join(sorted({l for g in guarded for l in g.locks}))
            out.append(Finding(
                "DC100", sf.path, w.line, symbol,
                f"{symbol} is written under self.{locks} elsewhere but "
                f"{w.method}() writes it with no lock held — the guard is "
                "advisory; annotate guarded-by/unguarded-ok or take the lock",
            ))
            continue

        entry_writes = [
            w for w in unguarded if w.method in info.thread_entries
        ]
        if entry_writes:
            others = {
                a.method for a in accs
                if a.method not in _INIT_METHODS
                and a.method != entry_writes[0].method
            }
            if others:
                w = entry_writes[0]
                out.append(Finding(
                    "DC101", sf.path, w.line, symbol,
                    f"{symbol} is written without a lock in thread-entry "
                    f"method {w.method}() and also touched by "
                    f"{', '.join(sorted(others))} — cross-thread access "
                    "needs a lock or an unguarded-ok(reason) annotation",
                ))
                continue

        if has_concurrency:
            augs = [w for w in unguarded if w.kind == "aug"]
            if augs:
                w = augs[0]
                out.append(Finding(
                    "DC103", sf.path, w.line, symbol,
                    f"non-atomic read-modify-write of {symbol} in "
                    f"{w.method}() with no lock held, in a class that owns "
                    "locks/threads — concurrent updates lose increments",
                ))
    return out


def _skip(path: str) -> bool:
    parts = path.split("/")
    return any(seg in _SKIP_SEGMENTS for seg in parts[:-1])


@register
def check(files: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in files:
        if _skip(sf.path):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(_check_class(sf, _scan_class(sf, node)))
    return out
