"""Resource-lifecycle analysis (DC120, DC121).

Tracks this project's acquire/release pairs through one function at a
time, ``finally``/context-manager aware, with release-through-helper
resolution via the shared call graph:

* ``PageAllocator`` pages — ``x = <...>.alloc(n)`` ... ``free(x)``;
* relay/directory connections — ``c = RelayClient(...)`` /
  ``DirectoryClient(...)`` ... ``c.close()``;
* raw sockets — ``socket.create_connection`` / ``socket.socket``.

**DC120** — an exception path escapes the window between the acquire and
its release/ownership-transfer without the release: under fault
injection that's a leaked page (HBM capacity AND disagg-wire unit) or a
leaked socket per retry.  The window ends when the resource is
*published* (stored into long-lived state, returned, or handed to
another owner) — after that the new owner's lifecycle applies.  A
``with ... as x:`` acquire is always clean.  Acquires stored directly on
``self`` are instance-owned (teardown's concern, not this function's).

**DC121** — the same resource released twice along one straight-line
block: a double-free (``PageAllocator.free`` raises on it; a socket
double-close masks real errors).

A deliberate escape takes ``# distcheck: leak-ok(reason)`` on the
acquire line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    Finding,
    SourceFile,
    call_name,
    dotted,
    graph_for,
    register,
)

_CTORS = {"RelayClient", "DirectoryClient"}
_SOCKET_CTORS = {"socket.create_connection", "socket.socket"}
_RELEASE_ATTRS = {"close", "free", "release"}
# Calls that never take ownership of (or raise because of) an argument.
_TRANSPARENT = {
    "len", "bool", "repr", "str", "print", "enumerate", "list", "sorted",
    "zip", "range", "min", "max", "sum", "tuple", "set", "dict", "reversed",
    "isinstance", "id", "iter", "next", "float", "int", "abs", "format",
}
# Container/bookkeeping method calls that cannot realistically raise —
# they don't open an exception path out of the acquire window.
_NONRAISING_ATTRS = {
    "append", "extend", "insert", "add", "discard", "update", "setdefault",
    "items", "keys", "values", "copy", "clear", "is_set",
}


def _is_acquire(call: ast.Call) -> Optional[str]:
    """'pages' | 'conn' | None — what kind of resource this call acquires."""
    name = call_name(call)
    short = name.rsplit(".", 1)[-1]
    if short == "alloc":
        return "pages"
    if short in _CTORS:
        return "conn"
    if name in _SOCKET_CTORS:
        return "conn"
    return None


def _walk_no_nested(fn_node) -> List[ast.AST]:
    """All nodes of a function body, excluding nested def/class subtrees."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(fn_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _refs_value(node: ast.AST, name: str) -> bool:
    """``name`` appears as a *value* — not merely as the receiver of a
    method call (``client.get(...)`` uses client, it doesn't hand it off)."""
    excluded: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            excluded.update(id(f) for f in ast.walk(n.func))
    return any(
        isinstance(n, ast.Name) and n.id == name and id(n) not in excluded
        for n in ast.walk(node)
    )


class _FnScan:
    def __init__(self, sf: SourceFile, fn_node, qual: str, graph):
        self.sf = sf
        self.fn = fn_node
        self.qual = qual
        self.graph = graph
        self.cls = qual.rsplit(".", 2)[0] if "." in qual else None
        self.nodes = _walk_no_nested(fn_node)
        self.parents: Dict[int, ast.AST] = {}
        for node in ast.walk(fn_node):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node

    # -- classification -------------------------------------------------------

    def _is_release_of(self, call: ast.Call, target: str) -> bool:
        """client.close() / allocator.free(s.pages) / helper(client) where
        the resolved helper releases its bound parameter."""
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in _RELEASE_ATTRS:
                if dotted(call.func.value) == target:
                    return True
                if any(dotted(a) == target for a in call.args):
                    return True
        for pos, arg in enumerate(call.args):
            if dotted(arg) != target:
                continue
            callee = self.graph.resolve_call(
                self.sf, call, self.cls if self.cls else None
            )
            if callee is None:
                continue
            param = callee.param_for_arg(pos)
            if param is None:
                continue
            for sub in ast.walk(callee.node):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ) and sub.func.attr in _RELEASE_ATTRS:
                    if dotted(sub.func.value) == param or any(
                        dotted(a) == param for a in sub.args
                    ):
                        return True
        return False

    def _is_publication(self, node: ast.AST, target: str, base: str) -> bool:
        if isinstance(node, ast.Return):
            return node.value is not None and (
                _refs_value(node.value, base)
            )
        if isinstance(node, ast.Assign):
            lhs_rooted = all(
                dotted(t).split(".")[0] == base or dotted(t) == ""
                and isinstance(t, ast.Subscript)
                and dotted(t.value).split(".")[0] == base
                for t in node.targets
            )
            if not lhs_rooted and _refs_value(node.value, base):
                return True
            return False
        if isinstance(node, ast.Call):
            short = call_name(node).rsplit(".", 1)[-1]
            if short in _TRANSPARENT:
                return False
            return any(_refs_value(a, base) for a in node.args) or any(
                _refs_value(kw.value, base) for kw in node.keywords
            )
        return False

    def _base_is_fresh(self, target: str, acq_line: int) -> bool:
        """True when the resource is anchored to a freshly built local —
        a leak candidate.  Dotted targets qualify only when their base was
        constructed in this function (``s = Session(...)``); loop vars,
        parameters, and self-derived objects are owned elsewhere."""
        base = target.split(".")[0]
        if base == "self":
            return False
        if base == target:
            return True
        for node in self.nodes:
            if isinstance(node, ast.Assign) and node.lineno < acq_line:
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == base:
                        v = node.value
                        if isinstance(v, ast.Call):
                            short = call_name(v).rsplit(".", 1)[-1]
                            if short[:1].isupper():
                                return True
                        return False
        return False

    def _handler_nodes_of_acquire(self, acquire: ast.AST) -> Set[int]:
        """Nodes inside ``except`` handlers of any ``try`` whose body holds
        the acquire: if the acquire raised, the resource was never bound —
        those handlers cannot leak it and are not part of the window."""
        out: Set[int] = set()
        node = acquire
        while id(node) in self.parents:
            child, node = node, self.parents[id(node)]
            if isinstance(node, ast.Try) and child in node.body:
                for h in node.handlers:
                    for stmt in h.body:
                        out.update(id(n) for n in ast.walk(stmt))
        return out

    def _protected(self, risky: ast.AST, target: str) -> bool:
        node = risky
        while id(node) in self.parents:
            node = self.parents[id(node)]
            if isinstance(node, ast.Try):
                for blk in [node.finalbody] + [h.body for h in node.handlers]:
                    for stmt in blk:
                        for sub in ast.walk(stmt):
                            if isinstance(sub, ast.Call) and (
                                self._is_release_of(sub, target)
                            ):
                                return True
        return False

    # -- checks ---------------------------------------------------------------

    def check(self) -> List[Finding]:
        out: List[Finding] = []
        acquires: List[Tuple[ast.Assign, str, str]] = []
        for node in self.nodes:
            if not isinstance(node, ast.Assign) or not node.targets:
                continue
            kinds = [
                _is_acquire(c)
                for c in ast.walk(node.value)
                if isinstance(c, ast.Call)
            ]
            kind = next((k for k in kinds if k), None)
            if kind is None:
                continue
            target = dotted(node.targets[0])
            if not target or target.startswith("self."):
                continue
            if not self._base_is_fresh(target, node.lineno):
                continue
            acquires.append((node, target, kind))

        fn_end = max(
            (getattr(n, "end_lineno", None) or n.lineno
             for n in self.nodes if hasattr(n, "lineno")),
            default=0,
        )
        for node, target, kind in acquires:
            if self.sf.ann.at(node.lineno, "leak-ok") is not None:
                continue
            base = target.split(".")[0]
            # Window: from the acquire to the first release or publication.
            end = fn_end + 1
            for other in self.nodes:
                line = getattr(other, "lineno", None)
                if line is None or line <= node.lineno:
                    continue
                if isinstance(other, ast.Call) and self._is_release_of(
                    other, target
                ):
                    end = min(end, line)
                elif self._is_publication(other, target, base):
                    end = min(end, line)
            handler_ids = self._handler_nodes_of_acquire(node)
            risky = [
                c for c in self.nodes
                if isinstance(c, ast.Call)
                and node.lineno < c.lineno < end
                and id(c) not in handler_ids
                and call_name(c).rsplit(".", 1)[-1] not in _TRANSPARENT
                and not (
                    isinstance(c.func, ast.Attribute)
                    and c.func.attr in _NONRAISING_ATTRS
                )
                and not self._is_release_of(c, target)
            ]
            unprotected = [
                c for c in risky if not self._protected(c, target)
            ]
            if unprotected:
                first = min(unprotected, key=lambda c: c.lineno)
                what = "allocated pages" if kind == "pages" else "connection"
                out.append(Finding(
                    "DC120", self.sf.path, node.lineno,
                    f"{self.qual}.{target}",
                    f"{what} '{target}' can leak: "
                    f"{call_name(first) or 'a call'}() at line {first.lineno} "
                    "may raise before the release/ownership transfer and no "
                    "finally/except releases it — free it on the error path "
                    "or annotate leak-ok(reason)",
                ))

        # DC121: two releases of one target in the same straight-line block.
        for body in self._bodies():
            seen: Dict[str, int] = {}
            for stmt in body:
                if not isinstance(stmt, ast.Expr) or not isinstance(
                    stmt.value, ast.Call
                ):
                    continue
                call = stmt.value
                tgt = None
                if isinstance(call.func, ast.Attribute) and (
                    call.func.attr in _RELEASE_ATTRS
                ):
                    recv = dotted(call.func.value)
                    args = [dotted(a) for a in call.args if dotted(a)]
                    tgt = args[0] if args else recv
                if tgt:
                    if tgt in seen:
                        out.append(Finding(
                            "DC121", self.sf.path, stmt.lineno,
                            f"{self.qual}.{tgt}",
                            f"'{tgt}' is released twice on the same path "
                            f"(first at line {seen[tgt]}) — double-free/"
                            "double-close",
                        ))
                    else:
                        seen[tgt] = stmt.lineno
        return out

    def _bodies(self) -> List[List[ast.stmt]]:
        out: List[List[ast.stmt]] = [self.fn.body]
        for node in self.nodes:
            for field in ("body", "orelse", "finalbody"):
                blk = getattr(node, field, None)
                if isinstance(blk, list) and blk and isinstance(
                    blk[0], ast.stmt
                ):
                    out.append(blk)
        return out


@register
def check(files: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    graph = graph_for(files)
    for sf in files:
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_FnScan(sf, node, node.name, graph).check())
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        out.extend(_FnScan(
                            sf, sub, f"{node.name}.{sub.name}", graph
                        ).check())
    return out
