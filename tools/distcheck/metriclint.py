"""Metrics-registry lint (DC400-DC402).

Every metric name handed to ``Metrics`` (``.counter`` / ``.gauge`` /
``.observe`` / ``.timer`` / the read-side ``get_*`` / ``percentile``,
plus ``prometheus(extra_gauges={...})`` keys) must be declared once in
the central ``METRICS`` registry (``utils/metrics.py``) with a matching
kind. That kills name drift between emitters and the ``/metrics`` docs:
a typo'd counter shows up as DC400 at the emit site instead of as a
mysteriously flat graph.

* **DC400** — name used but not declared (or declared with another kind).
* **DC401** — registry entry never used by any scanned call site (dead
  doc — delete it or wire the emitter). Only reported when the scan
  includes the registry itself and at least one call site.
* **DC402** — registry entry violating prometheus naming rules: names
  must be ``snake_case``; counters must not end in ``_total`` /
  ``_seconds`` / ``_count`` and summaries must not end in ``_total`` /
  ``_seconds`` (the exposition layer appends those suffixes itself).

Dynamic names: f-strings become ``*`` wildcard patterns and must match a
wildcard registry entry (``pool_batches_size_*``). A name computed some
other way needs ``# distcheck: metric(name_a, name_b)`` on the call line
enumerating what it can resolve to (a local single-assignment from a
string conditional is resolved automatically).
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import Dict, List, Optional, Tuple

from .core import Finding, SourceFile, is_subset_scan, register

_EMITTERS = {
    "counter": "counter",
    "get_counter": "counter",
    "gauge": "gauge",
    "get_gauge": "gauge",
    "observe": "summary",
    "timer": "summary",
    "percentile": "summary",
}
_KINDS = ("counter", "gauge", "summary")
_NAME_OK = re.compile(r"^[a-z][a-z0-9_*]*$")
_BAD_SUFFIX = {
    "counter": ("_total", "_seconds", "_count"),
    "summary": ("_total", "_seconds"),
    "gauge": ("_total",),
}


def _metrics_receiver(func: ast.Attribute) -> bool:
    base = func.value
    if isinstance(base, ast.Name):
        return base.id in ("metrics", "m")
    if isinstance(base, ast.Attribute):
        return base.attr == "metrics"
    return False


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fstring_pattern(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        s = _const_str(v)
        parts.append(s if s is not None else "*")
    return "".join(parts)


def _local_str_values(fn_node, name: str) -> Optional[List[str]]:
    """Resolve a Name used as a metric name: single assignment in the
    enclosing function from a string constant / conditional of them."""
    assigns = []
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    assigns.append(sub.value)
    if len(assigns) != 1:
        return None
    v = assigns[0]
    if isinstance(v, ast.IfExp):
        a, b = _const_str(v.body), _const_str(v.orelse)
        if a is not None and b is not None:
            return [a, b]
    s = _const_str(v)
    return [s] if s is not None else None


def _registry_of(sf: SourceFile) -> Dict[str, Tuple[str, int]]:
    """{name: (kind, line)} from a module-level ``METRICS = {...}``."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in sf.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "METRICS"
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            name = _const_str(k) if k is not None else None
            if name is None:
                continue
            kind = ""
            if isinstance(v, (ast.Tuple, ast.List)) and v.elts:
                kind = _const_str(v.elts[0]) or ""
            elif _const_str(v) is not None:
                kind = _const_str(v) or ""
            out[name] = (kind, k.lineno)
    return out


def _matches(pattern: str, registry: Dict[str, Tuple[str, int]]):
    """Registry entry matching a use-pattern (either side may hold '*')."""
    if pattern in registry:
        return pattern
    for key in registry:
        if "*" in key and fnmatch.fnmatchcase(pattern.replace("*", "x"), key):
            return key
        if "*" in pattern and fnmatch.fnmatchcase(key, pattern):
            return key
    return None


@register
def check(files: List[SourceFile]) -> List[Finding]:
    registry: Dict[str, Tuple[str, int]] = {}
    registry_file: Optional[SourceFile] = None
    for sf in files:
        reg = _registry_of(sf)
        if reg:
            registry.update(reg)
            registry_file = sf
    out: List[Finding] = []
    if registry_file is not None:
        for name, (kind, line) in sorted(registry.items()):
            if kind not in _KINDS:
                out.append(Finding(
                    "DC402", registry_file.path, line, f"METRICS.{name}",
                    f"registry entry '{name}' has kind '{kind}' — expected "
                    f"one of {', '.join(_KINDS)}",
                ))
                continue
            if not _NAME_OK.match(name):
                out.append(Finding(
                    "DC402", registry_file.path, line, f"METRICS.{name}",
                    f"registry entry '{name}' is not snake_case",
                ))
            if name.rstrip("*").endswith(_BAD_SUFFIX[kind]):
                out.append(Finding(
                    "DC402", registry_file.path, line, f"METRICS.{name}",
                    f"{kind} '{name}' must not carry a reserved prometheus "
                    "suffix — the exposition layer appends it",
                ))
    if not registry:
        return out  # nothing to check against (subset scan)

    used: Dict[str, int] = {}

    def _use(sf: SourceFile, line: int, pattern: str, kind: str, sym: str):
        key = _matches(pattern, registry)
        if key is None:
            out.append(Finding(
                "DC400", sf.path, line, sym,
                f"metric '{pattern}' ({kind}) is not declared in the "
                "METRICS registry — add it (or fix the name drift)",
            ))
            return
        used[key] = used.get(key, 0) + 1
        decl_kind = registry[key][0]
        if decl_kind in _KINDS and decl_kind != kind:
            out.append(Finding(
                "DC400", sf.path, line, sym,
                f"metric '{pattern}' is declared as a {decl_kind} but used "
                f"as a {kind}",
            ))

    any_call_site = False
    for sf in files:
        for fn_node in ast.walk(sf.tree):
            if not isinstance(
                fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                if attr == "prometheus":
                    for kw in node.keywords:
                        val = kw.value
                        if isinstance(val, ast.Name):
                            # e.g. gauges sampled on the event loop, render
                            # pushed to the executor — resolve the local.
                            assigns = [
                                s.value for s in ast.walk(fn_node)
                                if isinstance(s, ast.Assign) and any(
                                    isinstance(t, ast.Name)
                                    and t.id == val.id
                                    for t in s.targets
                                )
                            ]
                            if len(assigns) == 1:
                                val = assigns[0]
                        if kw.arg == "extra_gauges" and isinstance(
                            val, ast.Dict
                        ):
                            any_call_site = True
                            for k in val.keys:
                                s = _const_str(k) if k is not None else None
                                if s is not None:
                                    _use(sf, k.lineno, s, "gauge",
                                         f"extra_gauges.{s}")
                    continue
                kind = _EMITTERS.get(attr)
                if kind is None or not _metrics_receiver(node.func):
                    continue
                if not node.args:
                    continue
                any_call_site = True
                arg = node.args[0]
                sym = f"metrics.{attr}"
                declared = sf.ann.at(node.lineno, "metric")
                if declared is not None:
                    for nm in declared.split(","):
                        nm = nm.strip()
                        if nm:
                            _use(sf, node.lineno, nm, kind, sym)
                    continue
                s = _const_str(arg)
                if s is not None:
                    _use(sf, arg.lineno, s, kind, sym)
                elif isinstance(arg, ast.JoinedStr):
                    _use(sf, arg.lineno, _fstring_pattern(arg), kind, sym)
                elif isinstance(arg, ast.IfExp) and (
                    _const_str(arg.body) is not None
                    and _const_str(arg.orelse) is not None
                ):
                    _use(sf, arg.lineno, _const_str(arg.body), kind, sym)
                    _use(sf, arg.lineno, _const_str(arg.orelse), kind, sym)
                elif isinstance(arg, ast.Name):
                    vals = _local_str_values(fn_node, arg.id)
                    if vals:
                        for nm in vals:
                            _use(sf, arg.lineno, nm, kind, sym)
                    else:
                        out.append(Finding(
                            "DC400", sf.path, arg.lineno, sym,
                            f"metric name '{arg.id}' is not statically "
                            "resolvable — annotate the call with "
                            "# distcheck: metric(name, ...)",
                        ))
                else:
                    out.append(Finding(
                        "DC400", sf.path, arg.lineno, sym,
                        "metric name expression is not statically "
                        "resolvable — annotate the call with "
                        "# distcheck: metric(name, ...)",
                    ))

    # Dead-declaration evidence is "no scanned call site emits it" — on a
    # subset scan (--changed) the emitters are usually the files NOT in
    # the scan, so the closed-world check stays silent.
    if registry_file is not None and any_call_site and not is_subset_scan():
        for name, (kind, line) in sorted(registry.items()):
            if name not in used:
                out.append(Finding(
                    "DC401", registry_file.path, line, f"METRICS.{name}",
                    f"registry entry '{name}' is never emitted by any "
                    "scanned call site — dead declaration",
                ))
    return out
