"""Relay-frame schema consistency (DC500, DC501).

Producers build header dicts and hand them to ``pack_frame(header, …)``
(or kv_codec's ``_pack``); consumers take headers back from
``unpack_frame`` / ``_unpack`` and read fields via ``header.get("k")`` /
``header["k"]``. The wire schema lives only in these dict literals — the
exact drift this checker pins down:

* **DC500** — a consumer reads a header field no producer ever writes
  (typo, or a producer was changed without its consumers).
* **DC501** — a producer writes a field no consumer ever reads (dead
  payload bytes on every frame, or the consumer was dropped).

Extraction is whole-program across the scanned set: produced keys come
from dict literals (including ``{**base, "k": v}`` spreads and
``dict(base, k=v)`` resolved through local single assignments, and
``{k: h.get(k) for k in _FIELDS}`` comprehensions over module-level
tuples); consumed keys follow the header variable interprocedurally one
call deep into same-module functions. A header that escapes beyond that
(stored, returned, forwarded wholesale) counts as consuming everything,
so DC501 only fires in a closed world. Both checks need at least one
producer AND one consumer in the scan — a subset scan stays silent.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    Finding,
    SourceFile,
    call_name,
    graph_for,
    is_subset_scan,
    register,
)

_PACKERS = {"pack_frame", "_pack"}
_UNPACKERS = {"unpack_frame", "_unpack"}


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _module_str_seqs(tree: ast.Module) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List, ast.Set)):
            continue
        vals = [_const_str(e) for e in node.value.elts]
        if any(v is None for v in vals):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = [v for v in vals if v is not None]
    return out


class _Producer:
    def __init__(self):
        self.keys: Dict[str, Tuple[str, int]] = {}  # key -> (path, line)
        self.open = False  # unresolvable part: unknown extra keys


def _dict_keys(
    node: ast.AST,
    fn_node: ast.AST,
    mod_seqs: Dict[str, List[str]],
    depth: int = 0,
) -> Tuple[Set[str], bool]:
    """(keys, open) for a header expression."""
    if depth > 4:
        return set(), True
    if isinstance(node, ast.Dict):
        keys: Set[str] = set()
        opened = False
        for k, v in zip(node.keys, node.values):
            if k is None:  # {**spread}
                sub, o = _dict_keys(v, fn_node, mod_seqs, depth + 1)
                keys |= sub
                opened |= o
            else:
                s = _const_str(k)
                if s is None:
                    opened = True
                else:
                    keys.add(s)
        return keys, opened
    if isinstance(node, ast.Call) and call_name(node) == "dict":
        keys, opened = set(), False
        if node.args:
            sub, o = _dict_keys(node.args[0], fn_node, mod_seqs, depth + 1)
            keys |= sub
            opened |= o
        for kw in node.keywords:
            if kw.arg is None:
                sub, o = _dict_keys(kw.value, fn_node, mod_seqs, depth + 1)
                keys |= sub
                opened |= o
            else:
                keys.add(kw.arg)
        return keys, opened
    if isinstance(node, ast.DictComp):
        it = node.generators[0].iter if node.generators else None
        if isinstance(it, ast.Name) and it.id in mod_seqs:
            return set(mod_seqs[it.id]), False
        return set(), True
    if isinstance(node, ast.Name):
        # Resolve local assignments within the enclosing function; several
        # (e.g. one per branch) union — the wire may carry any of them.
        assigns = []
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == node.id:
                        assigns.append(sub.value)
        if assigns:
            keys: Set[str] = set()
            opened = False
            for a in assigns:
                sub_keys, o = _dict_keys(a, fn_node, mod_seqs, depth + 1)
                keys |= sub_keys
                opened |= o
            return keys, opened
        return set(), True
    return set(), True


class _ParamUse:
    """How one function uses one of its dict parameters."""

    def __init__(self):
        self.keys: Dict[str, int] = {}  # key -> line
        self.escapes = False
        self.forwards: List[Tuple[str, int]] = []  # (callee, arg position)


def _loop_vars(fn_node, mod_seqs: Dict[str, List[str]]) -> Dict[str, List[str]]:
    """Loop/comprehension variables iterating a module-level str tuple:
    ``for k in _FIELDS`` makes ``h.get(k)`` consume every field."""
    out: Dict[str, List[str]] = {}
    for node in ast.walk(fn_node):
        gens = []
        if isinstance(node, ast.For):
            gens = [(node.target, node.iter)]
        elif isinstance(node, (ast.DictComp, ast.SetComp, ast.ListComp,
                               ast.GeneratorExp)):
            gens = [(g.target, g.iter) for g in node.generators]
        for tgt, it in gens:
            if isinstance(tgt, ast.Name) and isinstance(it, ast.Name) and (
                it.id in mod_seqs
            ):
                out[tgt.id] = mod_seqs[it.id]
    return out


def _scan_var_uses(
    fn_node, var: str, mod_seqs: Dict[str, List[str]]
) -> _ParamUse:
    """Every recognized consumption of ``var`` is accounted for; ANY other
    appearance of the name (stuffed into a tuple bound for a queue, stored,
    returned, iterated) marks an escape — the conservative reading is that
    an escaped header may be read in full somewhere we can't see."""
    use = _ParamUse()
    loop_vars = _loop_vars(fn_node, mod_seqs)
    handled: set = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            fname = call_name(node)
            if isinstance(node.func, ast.Attribute) and isinstance(
                node.func.value, ast.Name
            ) and node.func.value.id == var:
                handled.add(id(node.func.value))
                if node.func.attr == "get" and node.args:
                    s = _const_str(node.args[0])
                    if s is not None:
                        use.keys.setdefault(s, node.args[0].lineno)
                        continue
                    a = node.args[0]
                    if isinstance(a, ast.Name) and a.id in loop_vars:
                        for s in loop_vars[a.id]:
                            use.keys.setdefault(s, node.lineno)
                        continue
                use.escapes = True  # h.items(), h.keys(), h.pop(dyn), ...
                continue
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Name) and arg.id == var:
                    handled.add(id(arg))
                    short = fname.rsplit(".", 1)[-1]
                    if short in ("len", "bool", "repr", "str", "print"):
                        continue
                    use.forwards.append((short, i))
        elif isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Name) and node.value.id == var:
                handled.add(id(node.value))
                s = _const_str(node.slice)
                if s is not None:
                    use.keys.setdefault(s, node.lineno)
                elif isinstance(node.slice, ast.Name) and (
                    node.slice.id in loop_vars
                ):
                    for s in loop_vars[node.slice.id]:
                        use.keys.setdefault(s, node.lineno)
                else:
                    use.escapes = True
        elif isinstance(node, ast.Compare):
            # "k" in header
            if (
                len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and isinstance(node.comparators[0], ast.Name)
                and node.comparators[0].id == var
            ):
                handled.add(id(node.comparators[0]))
                s = _const_str(node.left)
                if s is not None:
                    use.keys.setdefault(s, node.lineno)
    for node in ast.walk(fn_node):
        if (
            isinstance(node, ast.Name)
            and node.id == var
            and isinstance(node.ctx, ast.Load)
            and id(node) not in handled
        ):
            use.escapes = True
            break
    return use


@register
def check(files: List[SourceFile]) -> List[Finding]:
    if is_subset_scan():
        # Schema drift is producer-set vs consumer-set evidence; a
        # --changed subset sees neither side in full.
        return []
    produced: Dict[str, Tuple[str, int]] = {}
    any_open_producer = False
    consumed: Dict[str, Tuple[str, int]] = {}
    wildcard_consumer = False
    n_producers = n_consumers = 0

    # Interprocedural follow rides the shared package-wide call graph
    # (core.CallGraph): same-module def lookup, self-param offset, and the
    # traversal depth cap all live there now.
    graph = graph_for(files)

    def consume_via(
        sf: SourceFile, fn_node, var: str, mod_seqs, depth: int
    ) -> None:
        nonlocal wildcard_consumer
        use = _scan_var_uses(fn_node, var, mod_seqs)
        for k, line in use.keys.items():
            consumed.setdefault(k, (sf.path, line))
        if use.escapes or depth >= graph.max_depth:
            if use.escapes:
                wildcard_consumer = True
            return
        for callee, pos in use.forwards:
            target = graph.any_def_in_module(sf.path, callee)
            if target is None:
                wildcard_consumer = True
                continue
            param = target.param_for_arg(pos)
            if param is not None:
                consume_via(sf, target.node, param, mod_seqs, depth + 1)

    for sf in files:
        mod_seqs = _module_str_seqs(sf.tree)
        for fn_node in ast.walk(sf.tree):
            if not isinstance(
                fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            for node in ast.walk(fn_node):
                if isinstance(node, ast.Call):
                    short = call_name(node).rsplit(".", 1)[-1]
                    if short in _PACKERS and node.args:
                        n_producers += 1
                        keys, opened = _dict_keys(
                            node.args[0], fn_node, mod_seqs
                        )
                        any_open_producer |= opened
                        for k in keys:
                            produced.setdefault(k, (sf.path, node.lineno))
                elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    short = call_name(node.value).rsplit(".", 1)[-1]
                    if short not in _UNPACKERS:
                        continue
                    n_consumers += 1
                    tgt = node.targets[0]
                    header_var = None
                    if isinstance(tgt, (ast.Tuple, ast.List)) and tgt.elts:
                        if isinstance(tgt.elts[0], ast.Name):
                            header_var = tgt.elts[0].id
                    elif isinstance(tgt, ast.Name):
                        header_var = tgt.id
                    if header_var and header_var != "_":
                        consume_via(sf, fn_node, header_var, mod_seqs, 0)

    out: List[Finding] = []
    if n_producers == 0 or n_consumers == 0:
        return out
    if not any_open_producer:
        for k, (path, line) in sorted(consumed.items()):
            if k not in produced:
                out.append(Finding(
                    "DC500", path, line, f"frame.{k}",
                    f"consumer reads frame header field '{k}' that no "
                    "producer in the scanned set ever writes — schema "
                    "drift (typo, or the producer changed)",
                ))
    if not wildcard_consumer:
        for k, (path, line) in sorted(produced.items()):
            if k not in consumed:
                out.append(Finding(
                    "DC501", path, line, f"frame.{k}",
                    f"producer writes frame header field '{k}' that no "
                    "consumer in the scanned set ever reads — dead bytes "
                    "on every frame",
                ))
    return out
