"""CLI: ``python -m tools.distcheck [paths...]``."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import DEFAULT_BASELINE, REPO_ROOT, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="distcheck",
        description="Project-invariant static analyzer (lock discipline, "
        "async blocking calls, PRNG/host-sync hygiene, metrics registry, "
        "relay-frame schema).",
    )
    ap.add_argument(
        "paths", nargs="*",
        default=[str(REPO_ROOT / "distributed_llm_inference_tpu")],
        help="files/directories to analyze (default: the package)",
    )
    ap.add_argument(
        "--baseline", type=Path, default=DEFAULT_BASELINE,
        help="suppression baseline file (default: tools/distcheck/"
        "baseline.txt)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report baselined findings too",
    )
    args = ap.parse_args(argv)
    baseline = None if args.no_baseline else args.baseline
    return run(args.paths, baseline=baseline)


if __name__ == "__main__":
    sys.exit(main())
