"""CLI: ``python -m tools.distcheck [paths...]``."""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

from .core import DEFAULT_BASELINE, REPO_ROOT, run


def changed_files(ref: str, roots) -> list:
    """``.py`` files changed vs ``ref`` (diff + untracked), restricted to
    the requested analysis roots."""
    out: set = set()
    for cmd in (
        ["git", "diff", "--name-only", ref, "--", "*.py"],
        ["git", "ls-files", "--others", "--exclude-standard", "--", "*.py"],
    ):
        proc = subprocess.run(
            cmd, cwd=REPO_ROOT, capture_output=True, text=True
        )
        if proc.returncode != 0:
            raise SystemExit(
                f"distcheck: --changed: {' '.join(cmd)} failed: "
                f"{proc.stderr.strip()}"
            )
        out.update(l.strip() for l in proc.stdout.splitlines() if l.strip())
    resolved = []
    root_paths = [Path(r).resolve() for r in roots]
    for rel in sorted(out):
        p = (REPO_ROOT / rel).resolve()
        if not p.is_file():
            continue  # deleted in the diff
        for r in root_paths:
            if p == r or (r.is_dir() and str(p).startswith(str(r) + "/")):
                resolved.append(str(p))
                break
    return resolved


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="distcheck",
        description="Project-invariant static analyzer (lock discipline, "
        "lock ordering, async blocking calls, resource lifecycle, reply "
        "guarantees, PRNG/host-sync hygiene, metrics registry, "
        "relay-frame schema).",
    )
    ap.add_argument(
        "paths", nargs="*",
        default=[str(REPO_ROOT / "distributed_llm_inference_tpu")],
        help="files/directories to analyze (default: the package)",
    )
    ap.add_argument(
        "--baseline", type=Path, default=DEFAULT_BASELINE,
        help="suppression baseline file (default: tools/distcheck/"
        "baseline.txt)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report baselined findings too",
    )
    ap.add_argument(
        "--strict-baseline", action="store_true",
        help="stale baseline entries (matching no finding) are an error, "
        "not a warning",
    )
    ap.add_argument(
        "--json", action="store_true", dest="json_out",
        help="machine-readable output: a JSON array of findings "
        "(path, line, id, symbol, message, fingerprint)",
    )
    ap.add_argument(
        "--changed", nargs="?", const="HEAD", default=None, metavar="REF",
        help="analyze only .py files changed vs a git ref (default HEAD). "
        "Whole-program checkers stay conservatively silent on subsets — "
        "this is the fast pre-commit loop, not the tier-1 gate",
    )
    ap.add_argument(
        "--timings", action="store_true",
        help="print per-checker wall time (the tier-1 budget line)",
    )
    args = ap.parse_args(argv)
    paths = args.paths
    subset = args.changed is not None
    if subset:
        paths = changed_files(args.changed, paths)
        if not paths:
            print(f"distcheck: no changed .py files vs {args.changed}")
            return 0
    baseline = None if args.no_baseline else args.baseline
    return run(
        paths,
        baseline=baseline,
        json_out=args.json_out,
        strict_baseline=args.strict_baseline,
        timings=args.timings,
        subset=subset,
    )


if __name__ == "__main__":
    sys.exit(main())
