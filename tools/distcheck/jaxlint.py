"""JAX discipline checks (DC300, DC301).

**DC300 — PRNG key reuse.** A key variable (assigned from
``jax.random.PRNGKey`` / ``split`` / ``fold_in`` / ``key``) that is
consumed by a sampling primitive more than once without an intervening
re-derivation reuses randomness — two draws become correlated and the
byte-exact parity contract across serving paths silently breaks. Also
flagged: consuming a key inside a loop whose last derivation happened
outside the loop (every iteration draws the same stream). ``split`` and
``fold_in`` are derivations, not consumptions. Annotate deliberate reuse
(e.g. common random numbers in a test harness) with
``# distcheck: key-reuse-ok(reason)``.

**DC301 — host sync in the tick hot path.** Within engine tick-path
functions (``step`` and the ``_*tick`` / ``_*dispatch`` / ``_*resolve``
/ ``_*flush`` family under ``engine/``), ``jax.device_get`` and
``.block_until_ready()`` force a device round-trip per call. The tick
budget allows exactly the amortized fetches the overlap design
documents — each of those carries ``# distcheck: host-sync-ok(reason)``;
anything new gets flagged so the ragged-kernel work can't quietly grow
the per-tick sync count.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from .core import Finding, SourceFile, call_name, register

_KEY_SOURCES = {
    "PRNGKey", "key", "split", "fold_in", "clone",
}
_DERIVE_FNS = {"split", "fold_in", "key", "PRNGKey", "clone", "wrap_key_data"}
_TICK_NAME = re.compile(
    r"^(step|_\w*(tick|dispatch|resolve|flush))$"
)


def _is_random_fn(name: str) -> Optional[str]:
    """'jax.random.categorical' -> 'categorical'; also 'random.foo' and
    bare re-exports like 'jrandom.foo'."""
    parts = name.split(".")
    if len(parts) >= 2 and parts[-2] in ("random", "jrandom"):
        return parts[-1]
    return None


class _KeyScan(ast.NodeVisitor):
    """Linear scan over one function: track per-variable key state.

    state[var] = (derive_line, loop_depth_at_derivation, consumed_count)
    """

    def __init__(self, sf: SourceFile, fn: str):
        self.sf = sf
        self.fn = fn
        self.state: Dict[str, Tuple[int, int, int]] = {}
        self.depth = 0
        self.out: List[Finding] = []

    def _assigned(self, tgt: ast.AST, from_key_source: bool) -> None:
        if isinstance(tgt, ast.Name):
            if from_key_source:
                self.state[tgt.id] = (tgt.lineno, self.depth, 0)
            else:
                self.state.pop(tgt.id, None)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._assigned(elt, from_key_source)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        is_key = False
        if isinstance(node.value, ast.Call):
            fn = _is_random_fn(call_name(node.value))
            is_key = fn in _DERIVE_FNS if fn else False
        for tgt in node.targets:
            self._assigned(tgt, is_key)

    def visit_Call(self, node: ast.Call) -> None:
        fn = _is_random_fn(call_name(node))
        if fn and fn not in _DERIVE_FNS:
            for arg in node.args[:1]:  # key is the first positional arg
                if isinstance(arg, ast.Name) and arg.id in self.state:
                    line0, depth0, count = self.state[arg.id]
                    ok = self.sf.ann.at(node.lineno, "key-reuse-ok")
                    if ok is None and (count >= 1 or depth0 < self.depth):
                        why = (
                            f"already consumed at line {line0}" if count >= 1
                            else f"derived outside this loop (line {line0})"
                        )
                        self.out.append(Finding(
                            "DC300", self.sf.path, node.lineno,
                            f"{self.fn}.{arg.id}",
                            f"PRNG key '{arg.id}' reused by "
                            f"jax.random.{fn} in {self.fn}() — {why}; "
                            "split/fold_in a fresh key per draw",
                        ))
                    self.state[arg.id] = (node.lineno, depth0, count + 1)
        self.generic_visit(node)

    def _loop(self, node) -> None:
        self.depth += 1
        self.generic_visit(node)
        self.depth -= 1

    visit_For = _loop
    visit_While = _loop

    def visit_FunctionDef(self, node):  # nested defs: separate unit
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def _host_sync_reason(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name.endswith("device_get") and (
        name.startswith("jax") or name == "device_get"
    ):
        return "jax.device_get"
    if isinstance(node.func, ast.Attribute) and (
        node.func.attr == "block_until_ready"
    ):
        return ".block_until_ready()"
    if name == "jax.block_until_ready":
        return "jax.block_until_ready"
    return None


def _check_tick(sf: SourceFile, node) -> List[Finding]:
    out: List[Finding] = []
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if sub is not node:
                continue
        if not isinstance(sub, ast.Call):
            continue
        reason = _host_sync_reason(sub)
        if reason is None:
            continue
        if sf.ann.at(sub.lineno, "host-sync-ok") is not None:
            continue
        out.append(Finding(
            "DC301", sf.path, sub.lineno, f"{node.name}:{reason}",
            f"host sync ({reason}) inside tick-path {node.name}() — each "
            "call stalls the decode tick on a device round-trip; batch it "
            "into the existing fetch or annotate host-sync-ok(reason)",
        ))
    return out


@register
def check(files: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in files:
        in_engine = "engine" in sf.path.split("/")[:-1] or (
            "fixtures" in sf.path
        )
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scan = _KeyScan(sf, node.name)
            for a in list(node.args.args) + list(node.args.kwonlyargs):
                # Parameters named like keys are tracked from entry.
                if a.arg == "key" or a.arg.endswith(("_key", "rng")):
                    scan.state[a.arg] = (node.lineno, 0, 0)
            for stmt in node.body:
                scan.visit(stmt)
            out.extend(scan.out)
            if in_engine and _TICK_NAME.match(node.name):
                out.extend(_check_tick(sf, node))
    return out
