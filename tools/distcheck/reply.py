"""Reply-guarantee analysis for frame consumers (DC130).

The PR-3 bug class this pins down: a relay/disagg frame consumer takes a
request off its queue and then bails — silent ``continue`` or bare
``return`` — without sending a reply frame, raising into a caller that
does, or hitting a declared error counter.  The requester learns nothing
and hangs out its full timeout.

Consumer entry points (the project's conventions, resolved through the
shared call graph):

* methods named ``_consume`` or ``_serve`` — the relay/hub serve loops;
* functions registered as a ``TaskPool`` batch handler
  (``TaskPool(self._process_batch, ...)``);
* direct callees of either that receive the decoded request/header
  (``self._handle(header, reply)``) — one hop through the call graph.

Within a consumer, every ``continue`` / bare ``return`` lexically after
the first frame decode (``unpack_frame`` / ``_unpack`` / ``json.loads``)
must be *guarded*: a reply primitive (``.put`` / ``.put_many`` /
``pack_frame`` / ``encode_error`` / ``encode_kv``), a delegation
(``.submit`` to a task pool), a declared error counter
(``metrics.counter``), or a ``raise`` must appear on the path before it
(preceding statements in its own block and ancestor blocks — conditional
``if`` siblings don't count, their branch may not have run; a ``try``
body doesn't vouch for its own ``except`` handler).  A ``return`` with a
value hands the reply to the caller and is exempt; exits before the
first decode never consumed a request.  A deliberate silent exit takes
``# distcheck: reply-ok(reason)`` on the exit line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    Finding,
    FunctionInfo,
    SourceFile,
    call_name,
    graph_for,
    register,
    self_attr,
)

_UNPACKERS = {"unpack_frame", "_unpack", "loads"}
_REPLY_ATTRS = {"put", "put_many"}
_REPLY_FNS = {"pack_frame", "encode_error", "encode_kv", "_pack"}
_ENTRY_NAMES = {"_consume", "_serve"}


def _is_guard(node: ast.AST) -> bool:
    if isinstance(node, ast.Raise):
        return True
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            _REPLY_ATTRS | {"submit", "counter"}
        ):
            return True
        if call_name(node).rsplit(".", 1)[-1] in _REPLY_FNS:
            return True
    return False


def _contains_guard(stmt: ast.stmt) -> bool:
    return any(_is_guard(n) for n in ast.walk(stmt))


class _Consumer:
    def __init__(self, sf: SourceFile, fi: FunctionInfo, first_line: int):
        self.sf = sf
        self.fi = fi
        self.first_line = first_line  # exits before this line are exempt


def _first_unpack(fn_node) -> Tuple[Optional[str], Optional[int]]:
    """(header var, line) of the first frame decode in the function."""
    best: Tuple[Optional[str], Optional[int]] = (None, None)
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        )):
            continue
        short = call_name(node.value).rsplit(".", 1)[-1]
        if short not in _UNPACKERS:
            continue
        tgt = node.targets[0]
        var = None
        if isinstance(tgt, (ast.Tuple, ast.List)) and tgt.elts and isinstance(
            tgt.elts[0], ast.Name
        ):
            var = tgt.elts[0].id
        elif isinstance(tgt, ast.Name):
            var = tgt.id
        if var and var != "_" and (
            best[1] is None or node.lineno < best[1]
        ):
            best = (var, node.lineno)
    return best


def _find_consumers(files, graph) -> List[_Consumer]:
    out: List[_Consumer] = []
    seen: Set[int] = set()

    def add(sf, fi, first_line):
        if id(fi.node) in seen:
            return
        seen.add(id(fi.node))
        out.append(_Consumer(sf, fi, first_line))

    entries: List[Tuple[SourceFile, FunctionInfo]] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if not isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    fi = FunctionInfo(sf, sub, sub.name, node.name)
                    if sub.name in _ENTRY_NAMES:
                        entries.append((sf, fi))
                    for call in ast.walk(sub):
                        # TaskPool(self._handler, ...) registers a consumer.
                        if isinstance(call, ast.Call) and call_name(
                            call
                        ).rsplit(".", 1)[-1] == "TaskPool" and call.args:
                            attr = self_attr(call.args[0])
                            if attr is not None:
                                handler = graph.method(sf, node.name, attr)
                                if handler is not None:
                                    add(sf, handler, 0)

    for sf, fi in entries:
        var, line = _first_unpack(fi.node)
        if line is None:
            continue  # no frame decode: not a request consumer
        add(sf, fi, line)
        # One hop: a callee handed the decoded request is a consumer too.
        for call in ast.walk(fi.node):
            if not isinstance(call, ast.Call):
                continue
            if not any(
                isinstance(a, ast.Name) and a.id == var for a in call.args
            ):
                continue
            callee = graph.resolve_call(sf, call, fi.cls)
            if callee is not None:
                add(callee.sf, callee, 0)
    return out


def _guarded(exit_stmt: ast.stmt, fn_node, parents: Dict[int, ast.AST]) -> bool:
    """True when a reply/delegation/counter/raise precedes the exit on its
    own path: preceding siblings in each ancestor block, recursively —
    but not inside preceding ``if`` statements (their branch may not have
    executed), and a ``try`` body doesn't vouch for its handlers."""
    child: ast.AST = exit_stmt
    while True:
        parent = parents.get(id(child))
        if parent is None or isinstance(
            parent, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            blocks = [parent.body] if parent is not None else []
        elif isinstance(parent, ast.Try) and isinstance(
            child, ast.ExceptHandler
        ):
            child = parent  # the try body may not have reached its reply
            continue
        else:
            blocks = [
                blk for blk in (
                    getattr(parent, "body", None),
                    getattr(parent, "orelse", None),
                    getattr(parent, "finalbody", None),
                )
                if isinstance(blk, list)
            ]
        for blk in blocks:
            if child in blk:
                for stmt in blk[: blk.index(child)]:
                    if isinstance(stmt, ast.If):
                        continue  # conditional sibling: may not have run
                    if _contains_guard(stmt):
                        return True
        if parent is None or isinstance(
            parent, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            return False
        child = parent


@register
def check(files: List[SourceFile]) -> List[Finding]:
    graph = graph_for(files)
    out: List[Finding] = []
    for c in _find_consumers(files, graph):
        fn_node = c.fi.node
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(fn_node):
            for sub in ast.iter_child_nodes(node):
                parents[id(sub)] = node
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Continue):
                kind = "continue"
            elif isinstance(node, ast.Return) and (
                node.value is None
                or (isinstance(node.value, ast.Constant)
                    and node.value.value is None)
            ):
                kind = "return"
            else:
                continue
            if node.lineno < c.first_line:
                continue  # before the first decode: nothing consumed yet
            if c.sf.ann.at(node.lineno, "reply-ok") is not None:
                continue
            if _guarded(node, fn_node, parents):
                continue
            out.append(Finding(
                "DC130", c.sf.path, node.lineno,
                f"{c.fi.qualname}:{kind}",
                f"consumer {c.fi.qualname}() drops a request with a silent "
                f"{kind}: no reply frame, no raise, no declared error "
                "counter on this path — the requester hangs out its "
                "timeout; reply/count it or annotate reply-ok(reason)",
            ))
    return out
