"""Blocking-call-in-event-loop lint (DC200).

Inside ``async def`` bodies, flag calls that park the event loop: the
accept loop and every in-flight SSE stream stall behind them.

* ``time.sleep(...)`` — use ``asyncio.sleep``.
* ``socket.*`` constructors and raw socket I/O methods.
* Device syncs: ``jax.device_get(...)``, ``.block_until_ready()``.
* Known-blocking project calls: ``.stop()`` / ``.join()`` (thread
  joins), ``.prometheus()`` / ``.snapshot()`` (lock + full-history
  sorts), ``Future.result()``, and relay round-trips (``.put`` / ``.get``
  / ``.put_many`` / ``.rpc`` / ``.ping`` on relay/client-named
  receivers).

The fix is ``await loop.run_in_executor(None, fn, ...)`` or handing the
work to the backend's driver thread. A call that is deliberately
blocking (bounded, cold path) takes ``# distcheck: blocking-ok(reason)``.

``await``-ed expressions are exempt by construction: awaiting
``run_in_executor(...)`` wraps the blocking call in a worker thread.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, SourceFile, call_name, dotted, register

# Attribute calls that block regardless of receiver.
_BLOCKING_ATTRS = {
    "stop": "joins worker threads",
    "join": "joins a thread",
    "block_until_ready": "synchronizes with the device",
    "prometheus": "takes the metrics lock and sorts full timing history",
    "snapshot": "takes the metrics lock and sorts full timing history",
    "log_snapshot": "takes the metrics lock and sorts full timing history",
    "result": "blocks on a Future",
}
# Relay round-trip methods, when the receiver looks like a relay/client.
_RELAY_ATTRS = {"put", "get", "put_many", "rpc", "ping", "cancel_queue"}
_RELAY_RECEIVERS = ("relay", "client", "conn", "_out", "_directory")
_SOCKET_IO = {
    "recv", "recv_into", "sendall", "send", "accept", "connect", "makefile",
}


def _blocking_reason(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name == "time.sleep":
        return "time.sleep blocks the event loop — use asyncio.sleep"
    if name.startswith("socket."):
        return f"raw {name}() in the event loop"
    if name in ("jax.device_get", "jax.block_until_ready"):
        return f"{name} synchronizes with the device"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in _BLOCKING_ATTRS:
            return f".{attr}() {_BLOCKING_ATTRS[attr]}"
        base = dotted(node.func.value).rsplit(".", 1)[-1].lower()
        if attr in _RELAY_ATTRS and any(
            key in base for key in _RELAY_RECEIVERS
        ):
            return f"relay round-trip .{attr}() on {dotted(node.func.value)}"
        if attr in _SOCKET_IO and ("sock" in base or "socket" in base):
            return f"socket .{attr}() in the event loop"
    return None


class _AsyncScan(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, fn: str):
        self.sf = sf
        self.fn = fn
        self.out: List[Finding] = []

    def visit_Await(self, node: ast.Await) -> None:
        # Whatever is awaited was made loop-safe (run_in_executor, native
        # coroutine) — don't descend into the awaited call itself, but do
        # scan its arguments.
        v = node.value
        if isinstance(v, ast.Call):
            for arg in list(v.args) + [kw.value for kw in v.keywords]:
                self.visit(arg)
        else:
            self.visit(v)

    def visit_Call(self, node: ast.Call) -> None:
        reason = _blocking_reason(node)
        if reason is not None and (
            self.sf.ann.at(node.lineno, "blocking-ok") is None
        ):
            self.out.append(Finding(
                "DC200", self.sf.path, node.lineno,
                f"{self.fn}:{call_name(node) or 'call'}",
                f"blocking call in async def {self.fn}(): {reason}; move "
                "it to run_in_executor or annotate blocking-ok(reason)",
            ))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # nested sync defs run elsewhere
        pass

    def visit_AsyncFunctionDef(self, node):  # scanned separately
        pass

    def visit_Lambda(self, node):  # executor thunks run off-loop
        pass


@register
def check(files: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                scan = _AsyncScan(sf, node.name)
                for stmt in node.body:
                    scan.visit(stmt)
                out.extend(scan.out)
    return out
