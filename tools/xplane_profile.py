"""CLI: aggregate TPU op durations from a jax trace's xplane.pb.

Thin wrapper over ``distributed_llm_inference_tpu.utils.xplane`` (the parser
lives in the package so bench.py and tests can use it too).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_llm_inference_tpu.utils.xplane import aggregate  # noqa: E402


def parse(path, top=40):
    total, agg, cnt = aggregate(path)
    print(f"line-total {total/1e9:.2f} ms over {sum(cnt.values())} events")
    for nm, d in agg.most_common(top):
        print(f"{d/1e9:9.3f} ms  x{cnt[nm]:<5} {nm[:120]}")


if __name__ == "__main__":
    parse(sys.argv[1])
