"""Minimal xplane.pb parser: aggregate TPU op durations from a jax trace."""
import collections
import sys


def read_varint(buf, i):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def fields(buf):
    i = 0
    n = len(buf)
    while i < n:
        tag, i = read_varint(buf, i)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = read_varint(buf, i)
            yield fnum, v
        elif wt == 2:
            ln, i = read_varint(buf, i)
            yield fnum, buf[i : i + ln]
            i += ln
        elif wt == 5:
            yield fnum, buf[i : i + 4]
            i += 4
        elif wt == 1:
            yield fnum, buf[i : i + 8]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")


def parse(path, top=40):
    space = open(path, "rb").read()
    for fnum, plane_buf in fields(space):
        if fnum != 1:
            continue
        name = None
        meta = {}
        lines = []
        for pf, pv in fields(plane_buf):
            if pf == 2 and isinstance(pv, bytes):
                name = pv.decode(errors="replace")
            elif pf == 4:  # event_metadata map entry
                mid, mname = None, ""
                for mf, mv in fields(pv):
                    if mf == 1:
                        mid = mv
                    elif mf == 2:
                        for ef, ev in fields(mv):
                            if ef == 2 and isinstance(ev, bytes):
                                mname = ev.decode(errors="replace")
                meta[mid] = mname
            elif pf == 3:
                lines.append(pv)
        if name != "/device:TPU:0":
            continue
        agg = collections.Counter()
        cnt = collections.Counter()
        for line_buf in lines:
            lname = ""
            evs = []
            for lf, lv in fields(line_buf):
                if lf == 2 and isinstance(lv, bytes):
                    try:
                        lname = lv.decode()
                    except Exception:
                        lname = repr(lv)
                elif lf == 4:
                    evs.append(lv)
            if "Step" in lname or "Modules" in lname:
                continue  # whole-program umbrella lines
            for ev in evs:
                mid, dur = None, 0
                for ef, v in fields(ev):
                    if ef == 1:
                        mid = v
                    elif ef == 3:
                        dur = v
                agg[meta.get(mid, f"id{mid}") ] += dur
                cnt[meta.get(mid, f"id{mid}")] += 1
        total = sum(agg.values())
        print(f"line-total {total/1e9:.2f} ms over {sum(cnt.values())} events")
        for nm, d in agg.most_common(top):
            print(f"{d/1e9:9.3f} ms  x{cnt[nm]:<5} {nm[:120]}")
        return


if __name__ == "__main__":
    parse(sys.argv[1])
